"""Namespace / Component / Endpoint model + endpoint serving.

Mirrors the reference component model (reference: lib/runtime/src/component.rs:73-321,
component/endpoint.rs:20-143): hierarchical naming, discoverable instance keys
held under the process's primary lease, a per-endpoint request subject, and a
push-endpoint loop that drives the handler and streams responses over the TCP
call-home plane.

Key layout (control-plane KV):
  instances/{ns}/components/{comp}/{endpoint}:{lease_hex}  -> msgpack instance info
Request subject:
  {ns}|{comp}.{endpoint}-{lease_hex}
"""

from __future__ import annotations

import asyncio
import inspect
from dataclasses import dataclass
from typing import Any, AsyncIterator, Callable, Optional

import msgpack

from dynamo_tpu.runtime.context import RequestContext, use_context
from dynamo_tpu.runtime.tcp import ConnectionInfo, call_home
from dynamo_tpu.utils import get_logger, tracing

log = get_logger("runtime.component")

INSTANCE_PREFIX = "instances"


def instance_key(ns: str, comp: str, endpoint: str, lease_id: int) -> str:
    return f"{INSTANCE_PREFIX}/{ns}/components/{comp}/{endpoint}:{lease_id:x}"


def endpoint_subject(ns: str, comp: str, endpoint: str, lease_id: int) -> str:
    return f"{ns}|{comp}.{endpoint}-{lease_id:x}"


@dataclass(frozen=True)
class EndpointInfo:
    namespace: str
    component: str
    endpoint: str
    instance_id: int  # lease id
    subject: str
    transport: str = "cplane-tcp"

    def to_wire(self) -> dict:
        return {
            "namespace": self.namespace,
            "component": self.component,
            "endpoint": self.endpoint,
            "instance_id": self.instance_id,
            "subject": self.subject,
            "transport": self.transport,
        }

    @classmethod
    def from_wire(cls, d: dict) -> "EndpointInfo":
        return cls(
            namespace=d["namespace"],
            component=d["component"],
            endpoint=d["endpoint"],
            instance_id=d["instance_id"],
            subject=d["subject"],
            transport=d.get("transport", "cplane-tcp"),
        )


class Namespace:
    def __init__(self, drt, name: str):
        self._drt = drt
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self._drt, self.name, name)


class Component:
    def __init__(self, drt, namespace: str, name: str):
        self._drt = drt
        self.namespace = namespace
        self.name = name

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self._drt, self.namespace, self.name, name)

    @property
    def event_subject_prefix(self) -> str:
        return f"{self.namespace}|{self.name}"

    def kv_events_subject(self) -> str:
        """Engine KV events channel (reference: kv_router/publisher.rs:33-74)."""
        return f"{self.event_subject_prefix}.kv_events"

    def stats_subject(self) -> str:
        """Service-stats scrape subject (reference: nats.rs scrape_service)."""
        return f"$SRV.STATS.{self.namespace}|{self.name}"


class Endpoint:
    def __init__(self, drt, namespace: str, component: str, name: str):
        self._drt = drt
        self.namespace = namespace
        self.component = component
        self.name = name
        self._stats_handler: Optional[Callable[[], dict]] = None

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.component}/{self.name}"

    # ---------------- serving ----------------

    def stats_handler(self, fn: Callable[[], dict]) -> None:
        self._stats_handler = fn

    async def serve_endpoint(
        self,
        handler: Callable[[Any], AsyncIterator[Any]],
        metrics: Optional[Callable[[], dict]] = None,
    ) -> "ServedEndpoint":
        """Register this endpoint for discovery and start its push loop.

        handler: async function or async-generator function taking the
        deserialized request; values it yields stream back to the caller.
        """
        drt = self._drt
        lease_id = drt.primary_lease.lease_id
        subject = endpoint_subject(self.namespace, self.component, self.name, lease_id)
        info = EndpointInfo(
            namespace=self.namespace,
            component=self.component,
            endpoint=self.name,
            instance_id=lease_id,
            subject=subject,
        )
        served = ServedEndpoint(drt, info, handler, metrics or self._stats_handler)
        await served.start()
        return served


class ServedEndpoint:
    """The push-endpoint loop (reference: pipeline/network/ingress/push_endpoint.rs)."""

    def __init__(self, drt, info: EndpointInfo, handler, stats_fn=None):
        self._drt = drt
        self.info = info
        self.handler = handler
        self.stats_fn = stats_fn
        self._tasks: set[asyncio.Task] = set()
        self._stats_subject = f"$SRV.STATS.{info.namespace}|{info.component}"

    async def start(self) -> None:
        client = self._drt.cplane
        await client.subscribe(self.info.subject, self._on_request)
        await client.subscribe(self._stats_subject, self._on_stats)
        await self._register()
        # broker outage or lease expiry: re-register once the connection (and
        # the lease, under its original id) is healed — subscriptions are
        # replayed by the client itself
        client.reconnect_hooks.append(self._register)
        log.info("serving %s (instance %x)", self.info.subject, self.info.instance_id)

    async def _register(self) -> None:
        key = instance_key(
            self.info.namespace, self.info.component, self.info.endpoint, self.info.instance_id
        )
        # put (not create-if-absent): re-registration after a heal must win
        await self._drt.cplane.kv_put(
            key, msgpack.packb(self.info.to_wire()), lease_id=self._drt.primary_lease.lease_id
        )

    async def stop(self) -> None:
        client = self._drt.cplane
        try:
            client.reconnect_hooks.remove(self._register)
        except ValueError:
            pass
        await client.unsubscribe(self.info.subject)
        key = instance_key(
            self.info.namespace, self.info.component, self.info.endpoint, self.info.instance_id
        )
        await client.kv_delete(key)
        for t in list(self._tasks):
            t.cancel()

    # ---------------- request handling ----------------

    def _on_request(self, msg: dict) -> None:
        task = asyncio.ensure_future(self._handle_request(msg["payload"]))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def _on_stats(self, msg: dict) -> None:
        if msg.get("reply"):
            stats = {}
            if self.stats_fn is not None:
                try:
                    stats = self.stats_fn()
                except Exception:
                    log.exception("stats handler failed")
            payload = {
                "instance_id": self.info.instance_id,
                "endpoint": self.info.endpoint,
                "subject": self.info.subject,
                "data": stats,
            }
            asyncio.ensure_future(self._drt.cplane.publish(msg["reply"], payload))

    async def _handle_request(self, payload: dict) -> None:
        conn_info = ConnectionInfo.from_wire(payload["conn_info"])
        request = msgpack.unpackb(payload["request"], raw=False)
        ctx = RequestContext.from_wire(payload["context"]) if payload.get("context") else None
        with use_context(ctx):
            # server-side hop span: the whole handler stream, on the timeline
            # of whatever trace id the caller shipped in the context
            with tracing.span(
                f"rpc.handle.{self.info.endpoint}",
                component=self.info.component,
            ):
                await self._run_handler(conn_info, request)

    async def _run_handler(self, conn_info, request) -> None:

        # Drive the handler to its first item BEFORE calling home: setup-time
        # failures ride the prologue (reference: network.rs:64-73 — first frame
        # is ResponseStreamPrologue ok-or-error), later failures are stream
        # error frames.
        first: Optional[Any] = None
        has_first = False
        stream = None
        try:
            result = self.handler(request)
            if inspect.isasyncgen(result):
                stream = result
                try:
                    first = await stream.__anext__()
                    has_first = True
                except StopAsyncIteration:
                    has_first = False
            elif inspect.iscoroutine(result):
                first = await result
                has_first = True
            else:
                raise TypeError("handler must be async or an async generator")
        except Exception as e:
            log.exception("handler for %s failed at setup", self.info.subject)
            try:
                await call_home(conn_info, error=f"{type(e).__name__}: {e}")
            except Exception:
                log.warning("failed to report error to caller")
            return

        sender = await call_home(conn_info)
        try:
            if has_first:
                await sender.send(msgpack.packb(first, use_bin_type=True))
            if stream is not None:
                async for item in stream:
                    await sender.send(msgpack.packb(item, use_bin_type=True))
            await sender.close()
        except Exception as e:
            log.exception("handler for %s failed mid-stream", self.info.subject)
            try:
                await sender.close(error=f"{type(e).__name__}: {e}")
            except Exception:
                log.warning("failed to report stream error to caller")
