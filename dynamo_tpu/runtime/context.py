"""Ambient request context: an id + metadata bag that flows through pipeline
stages and across network hops (reference: lib/runtime/src/pipeline/context.rs
Context<T>/StreamContext — request id and metadata ride every hop).

Propagation model (Python-native): a contextvar. The server side sets the
context around handler execution; any downstream ``Client.generate`` made
while handling picks it up automatically and ships it in the request envelope,
so metadata injected at the edge (e.g. a trace id stamped by the HTTP
frontend) is visible in every worker a request touches, with no plumbing
through handler signatures.
"""

from __future__ import annotations

import contextlib
import contextvars
import uuid
from dataclasses import dataclass, field
from typing import Iterator, Optional


@dataclass
class RequestContext:
    request_id: str
    metadata: dict = field(default_factory=dict)

    @property
    def trace_id(self) -> str:
        """The id that stitches this request's spans across hops: stamped into
        the metadata bag at the edge, falling back to the request id (so a
        context that never crossed an edge still yields one coherent trace)."""
        return self.metadata.get("trace_id") or self.request_id

    def ensure_trace_id(self) -> str:
        """Stamp the trace id into the metadata bag (idempotent) so downstream
        hops inherit it over the wire rather than re-deriving their own."""
        return self.metadata.setdefault("trace_id", self.request_id)

    def to_wire(self) -> dict:
        return {"request_id": self.request_id, "metadata": dict(self.metadata)}

    @classmethod
    def from_wire(cls, d: dict) -> "RequestContext":
        return cls(request_id=d.get("request_id", ""), metadata=dict(d.get("metadata") or {}))


_current: contextvars.ContextVar[Optional[RequestContext]] = contextvars.ContextVar(
    "dyntpu_request_context", default=None
)


def current_context() -> Optional[RequestContext]:
    """The ambient request context, or None outside a request."""
    return _current.get()


def new_context(request_id: Optional[str] = None, metadata: Optional[dict] = None) -> RequestContext:
    return RequestContext(request_id=request_id or uuid.uuid4().hex, metadata=dict(metadata or {}))


@contextlib.contextmanager
def use_context(ctx: Optional[RequestContext]) -> Iterator[None]:
    token = _current.set(ctx)
    try:
        yield
    finally:
        _current.reset(token)
