"""DistributedRuntime: the cluster handle.

Mirrors the reference DistributedRuntime (reference: lib/runtime/src/
distributed.rs:31-155): control-plane client + primary lease (liveness: lease
expiry => shutdown, shutdown => lease revoke) + lazy TCP response-plane server
+ namespace/component factory.
"""

from __future__ import annotations

import os
from typing import Optional

from dynamo_tpu.cplane.client import CplaneClient, Lease
from dynamo_tpu.runtime.client import Client
from dynamo_tpu.runtime.component import Namespace
from dynamo_tpu.runtime.runtime import CancellationToken, Runtime
from dynamo_tpu.runtime.tcp import TcpStreamServer
from dynamo_tpu.utils import get_logger

log = get_logger("runtime.distributed")

DEFAULT_CPLANE = "127.0.0.1:4222"


class DistributedRuntime:
    def __init__(
        self,
        runtime: Optional[Runtime] = None,
        cplane_address: Optional[str] = None,
        lease_ttl: float = 10.0,
    ):
        self.runtime = runtime or Runtime()
        self.cplane_address = cplane_address or os.environ.get("DYNTPU_CPLANE", DEFAULT_CPLANE)
        self.lease_ttl = lease_ttl
        self.cplane: Optional[CplaneClient] = None
        self.primary_lease: Optional[Lease] = None
        self.tcp_server = TcpStreamServer()
        self._clients: list[Client] = []
        self._connected = False

    @classmethod
    async def from_settings(cls, runtime: Optional[Runtime] = None) -> "DistributedRuntime":
        drt = cls(runtime=runtime)
        await drt.connect()
        return drt

    # ---------------- lifecycle ----------------

    async def connect(self) -> "DistributedRuntime":
        if self._connected:
            return self
        self.cplane = CplaneClient(self.cplane_address)
        await self.cplane.connect()
        self.primary_lease = await self.cplane.lease_create(ttl=self.lease_ttl)
        # liveness coupling, both directions (reference: etcd.rs:76-110)
        self.primary_lease.on_expired = self.runtime.shutdown
        self.cplane.on_disconnect = self.runtime.shutdown
        self.runtime.on_shutdown(self._shutdown_hook)
        self._connected = True
        return self

    async def _shutdown_hook(self) -> None:
        for client in self._clients:
            await client.stop()
        if self.primary_lease is not None:
            await self.primary_lease.revoke()
        await self.tcp_server.stop()
        if self.cplane is not None:
            await self.cplane.close()

    async def ensure_tcp_server(self) -> None:
        await self.tcp_server.start()

    @property
    def cancellation(self) -> CancellationToken:
        return self.runtime.cancellation

    # ---------------- factories ----------------

    def namespace(self, name: str) -> Namespace:
        return Namespace(self, name)

    async def client(self, namespace: str, component: str, endpoint: str) -> Client:
        c = Client(self, namespace, component, endpoint)
        await c.start()
        self._clients.append(c)
        return c

    async def endpoint_client(self, address: str) -> Client:
        """'dyn://ns.comp.endpoint' address form (reference: protocols.rs:30)."""
        if address.startswith("dyn://"):
            address = address[len("dyn://") :]
        parts = address.split(".")
        if len(parts) != 3:
            raise ValueError(f"bad endpoint address {address!r} (want ns.comp.endpoint)")
        return await self.client(*parts)
