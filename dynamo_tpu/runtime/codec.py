"""TwoPart wire codec for the RPC planes.

Same framing concept as the reference (reference: lib/runtime/src/pipeline/
network/codec/two_part.rs:23-160): a 24-byte prefix
``u64 header_len | u64 body_len | u64 xxh3(header||body)`` followed by header
bytes then body bytes. Header carries control messages (JSON/msgpack); body
carries the request/response payload.
"""

from __future__ import annotations

import asyncio
import struct
from dataclasses import dataclass

import xxhash

PREFIX = struct.Struct("<QQQ")
MAX_PART = 256 * 1024 * 1024


class CodecError(ValueError):
    pass


@dataclass(frozen=True)
class TwoPartMessage:
    header: bytes = b""
    body: bytes = b""


def encode(msg: TwoPartMessage) -> bytes:
    checksum = xxhash.xxh3_64_intdigest(msg.header + msg.body)
    return PREFIX.pack(len(msg.header), len(msg.body), checksum) + msg.header + msg.body


def decode(data: bytes) -> tuple[TwoPartMessage, bytes]:
    """Decode one message; returns (message, remaining_bytes). Raises
    IncompleteError via returning None is avoided — caller ensures enough data."""
    if len(data) < PREFIX.size:
        raise CodecError("short prefix")
    hlen, blen, checksum = PREFIX.unpack_from(data)
    if hlen > MAX_PART or blen > MAX_PART:
        raise CodecError("part too large")
    end = PREFIX.size + hlen + blen
    if len(data) < end:
        raise CodecError("short payload")
    header = data[PREFIX.size : PREFIX.size + hlen]
    body = data[PREFIX.size + hlen : end]
    if xxhash.xxh3_64_intdigest(header + body) != checksum:
        raise CodecError("checksum mismatch")
    return TwoPartMessage(header=header, body=body), data[end:]


async def read_message(reader: asyncio.StreamReader) -> TwoPartMessage:
    prefix = await reader.readexactly(PREFIX.size)
    hlen, blen, checksum = PREFIX.unpack(prefix)
    if hlen > MAX_PART or blen > MAX_PART:
        raise CodecError("part too large")
    header = await reader.readexactly(hlen) if hlen else b""
    body = await reader.readexactly(blen) if blen else b""
    if xxhash.xxh3_64_intdigest(header + body) != checksum:
        raise CodecError("checksum mismatch")
    return TwoPartMessage(header=header, body=body)


async def write_message(writer: asyncio.StreamWriter, msg: TwoPartMessage) -> None:
    writer.write(encode(msg))
    await writer.drain()
