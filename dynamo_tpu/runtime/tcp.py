"""Call-home TCP response plane.

Request flow (mirrors reference: lib/runtime/src/pipeline/network/tcp/server.rs:74-614,
egress/push.rs, ingress/push_handler.rs): the CALLER runs a TCP server and
registers a pending stream, obtaining ConnectionInfo{address, context_id}. The
request (pushed over the control plane) carries that ConnectionInfo; the WORKER
connects back ("calls home"), sends a handshake + prologue (ok or error), then
streams data frames and a final sentinel.

Frames are TwoPart messages: header = msgpack control
{kind: handshake|prologue|data|sentinel|error, ...}; body = payload bytes.
"""

from __future__ import annotations

import asyncio
import itertools
import socket
from dataclasses import dataclass
from typing import AsyncIterator, Optional

import msgpack

from dynamo_tpu.runtime.codec import TwoPartMessage, read_message, write_message
from dynamo_tpu.utils import get_logger

log = get_logger("runtime.tcp")


class ResponseStreamError(RuntimeError):
    """Remote prologue/stream error surfaced to the caller."""


@dataclass(frozen=True)
class ConnectionInfo:
    address: str  # host:port of the caller's stream server
    context_id: str

    def to_wire(self) -> dict:
        return {"address": self.address, "context_id": self.context_id}

    @classmethod
    def from_wire(cls, d: dict) -> "ConnectionInfo":
        return cls(address=d["address"], context_id=d["context_id"])


class StreamReceiver:
    """Caller-side view of one response stream."""

    def __init__(self, context_id: str):
        self.context_id = context_id
        self._queue: asyncio.Queue = asyncio.Queue()
        self.prologue_ok: Optional[asyncio.Future] = None

    async def __aiter__(self) -> AsyncIterator[bytes]:
        while True:
            item = await self._queue.get()
            if item is None:
                return
            if isinstance(item, Exception):
                raise item
            yield item


class TcpStreamServer:
    """Caller-side server; one per process, lazily started
    (reference: DistributedRuntime's lazy tcp server, distributed.rs:31-128)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, advertise_host: Optional[str] = None):
        self.host = host
        self.port = port
        self.advertise_host = advertise_host or host
        self._server: Optional[asyncio.AbstractServer] = None
        self._pending: dict[str, tuple[asyncio.Future, StreamReceiver]] = {}
        self._ctx_ids = itertools.count(1)

    async def start(self) -> None:
        if self._server is not None:
            return
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        if self.advertise_host in ("0.0.0.0", "::"):
            self.advertise_host = socket.gethostname()
        log.debug("tcp response plane on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    @property
    def address(self) -> str:
        return f"{self.advertise_host}:{self.port}"

    def register(self, context_id: Optional[str] = None) -> tuple[ConnectionInfo, StreamReceiver]:
        """Register a pending response stream before sending the request."""
        assert self._server is not None, "server not started"
        if context_id is None:
            context_id = f"ctx-{next(self._ctx_ids)}"
        receiver = StreamReceiver(context_id)
        connected: asyncio.Future = asyncio.get_running_loop().create_future()
        receiver.prologue_ok = connected
        self._pending[context_id] = (connected, receiver)
        return ConnectionInfo(address=self.address, context_id=context_id), receiver

    def unregister(self, context_id: str) -> None:
        entry = self._pending.pop(context_id, None)
        if entry is not None:
            fut, receiver = entry
            if not fut.done():
                fut.set_exception(ResponseStreamError("request cancelled"))
            receiver._queue.put_nowait(None)

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        context_id = None
        try:
            handshake = await read_message(reader)
            ctrl = msgpack.unpackb(handshake.header, raw=False)
            if ctrl.get("kind") != "handshake":
                raise ResponseStreamError("expected handshake")
            context_id = ctrl["context_id"]
            entry = self._pending.get(context_id)
            if entry is None:
                log.warning("handshake for unknown context %s", context_id)
                return
            connected, receiver = entry

            prologue = await read_message(reader)
            pctrl = msgpack.unpackb(prologue.header, raw=False)
            if pctrl.get("kind") == "error":
                err = ResponseStreamError(pctrl.get("message", "remote error"))
                if not connected.done():
                    connected.set_exception(err)
                receiver._queue.put_nowait(None)
                return
            if pctrl.get("kind") != "prologue":
                raise ResponseStreamError("expected prologue")
            if not connected.done():
                connected.set_result(True)

            while True:
                frame = await read_message(reader)
                fctrl = msgpack.unpackb(frame.header, raw=False) if frame.header else {"kind": "data"}
                kind = fctrl.get("kind")
                if kind == "data":
                    receiver._queue.put_nowait(frame.body)
                elif kind == "sentinel":
                    receiver._queue.put_nowait(None)
                    return
                elif kind == "error":
                    receiver._queue.put_nowait(
                        ResponseStreamError(fctrl.get("message", "remote stream error"))
                    )
                    return
        except (asyncio.IncompleteReadError, ConnectionResetError):
            if context_id and context_id in self._pending:
                _, receiver = self._pending[context_id]
                receiver._queue.put_nowait(ResponseStreamError("connection lost"))
        finally:
            if context_id:
                self._pending.pop(context_id, None)
            writer.close()


class StreamSender:
    """Worker-side sender for one response stream."""

    def __init__(self, writer: asyncio.StreamWriter):
        self._writer = writer

    async def send(self, payload: bytes) -> None:
        await write_message(
            self._writer,
            TwoPartMessage(header=msgpack.packb({"kind": "data"}), body=payload),
        )

    async def close(self, error: Optional[str] = None) -> None:
        try:
            if error is not None:
                header = msgpack.packb({"kind": "error", "message": error})
            else:
                header = msgpack.packb({"kind": "sentinel"})
            await write_message(self._writer, TwoPartMessage(header=header))
        finally:
            self._writer.close()


async def call_home(conn_info: ConnectionInfo, error: Optional[str] = None) -> Optional[StreamSender]:
    """Worker side: connect back to the caller and send handshake + prologue.

    With error set, sends an error prologue and returns None.
    """
    host, _, port = conn_info.address.rpartition(":")
    reader, writer = await asyncio.open_connection(host, int(port))
    await write_message(
        writer,
        TwoPartMessage(header=msgpack.packb({"kind": "handshake", "context_id": conn_info.context_id})),
    )
    if error is not None:
        await write_message(
            writer, TwoPartMessage(header=msgpack.packb({"kind": "error", "message": error}))
        )
        writer.close()
        return None
    await write_message(writer, TwoPartMessage(header=msgpack.packb({"kind": "prologue"})))
    return StreamSender(writer)
