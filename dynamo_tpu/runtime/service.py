"""Service stats scraping: broadcast a stats request to every endpoint of a
component's service group and gather replies within a deadline.

Mirrors the reference's NATS $SRV.STATS scrape (reference: lib/runtime/src/
service.rs:32-242, transports/nats.rs scrape_service).
"""

from __future__ import annotations

import asyncio
import uuid
from dataclasses import dataclass, field

from dynamo_tpu.utils import get_logger

log = get_logger("runtime.service")


@dataclass
class EndpointStats:
    instance_id: int
    endpoint: str
    subject: str
    data: dict = field(default_factory=dict)


@dataclass
class ServiceSet:
    endpoints: list[EndpointStats] = field(default_factory=list)


async def collect_service_stats(
    cplane,
    namespace: str,
    component: str,
    timeout: float = 0.5,
) -> ServiceSet:
    """Broadcast to $SRV.STATS.{ns}|{comp}; every live endpoint replies."""
    subject = f"$SRV.STATS.{namespace}|{component}"
    inbox = f"_INBOX.{uuid.uuid4().hex}"
    replies: list[dict] = []
    done = asyncio.Event()

    def on_reply(msg: dict) -> None:
        replies.append(msg["payload"])

    await cplane.subscribe(inbox, on_reply)
    try:
        await cplane.publish(subject, {"scrape": True}, reply=inbox)
        try:
            await asyncio.wait_for(done.wait(), timeout)
        except asyncio.TimeoutError:
            pass
    finally:
        await cplane.unsubscribe(inbox)
    return ServiceSet(
        endpoints=[
            EndpointStats(
                instance_id=r["instance_id"],
                endpoint=r["endpoint"],
                subject=r["subject"],
                data=r.get("data") or {},
            )
            for r in replies
        ]
    )
