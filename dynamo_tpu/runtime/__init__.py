"""Distributed runtime: Namespace/Component/Endpoint model, lease-based
discovery, two-plane RPC (request push over the control plane + call-home TCP
response streams).

The Python/asyncio re-design of the reference's dynamo-runtime crate
(reference: lib/runtime/src/, SURVEY.md §2.1).
"""

from dynamo_tpu.runtime.runtime import Runtime, CancellationToken
from dynamo_tpu.runtime.distributed import DistributedRuntime
